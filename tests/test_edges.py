"""Round-3 edge coverage: admission chain breadth, profiler endpoint,
incremental cluster protocol, multiple Topology trees."""
import json
import urllib.request

import pytest

from kai_scheduler_tpu.admission.webhooks import (AdmissionChain,
                                                  AdmissionError)
from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.framework.server import SchedulerServer
from kai_scheduler_tpu.runtime import snapshot
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.state import make_cluster


# --- admission breadth (ref pkg/admission/webhook/v1alpha2) --------------

def test_runtimeenforcement_sets_runtime_class():
    chain = AdmissionChain()
    pod = apis.Pod(name="p", group="g",
                   resources=apis.ResourceVec(1.0, 1.0, 1.0))
    chain.admit(pod)
    assert pod.labels["kai.scheduler/runtime-class"] == "tpu-runtime"
    cpu_pod = apis.Pod(name="c", group="g",
                       resources=apis.ResourceVec(0.0, 1.0, 1.0))
    chain.admit(cpu_pod)
    assert "kai.scheduler/runtime-class" not in cpu_pod.labels


def test_gpusharing_gate_rejects_when_disabled():
    from kai_scheduler_tpu.admission.webhooks import GpuSharingGate
    chain = AdmissionChain(plugins=[GpuSharingGate(sharing_enabled=False)])
    pod = apis.Pod(name="p", group="g", accel_portion=0.5)
    with pytest.raises(AdmissionError):
        chain.admit(pod)
    # whole-device pods pass the gate
    chain.admit(apis.Pod(name="w", group="g",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0)))


# --- server: profiler + incremental protocol ----------------------------

def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return json.loads(r.read())


def _post(port, path, doc):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_profiler_and_incremental_protocol():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=4.0, num_gangs=2, tasks_per_gang=2)
    cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
    doc = snapshot.dump_cluster(cluster)   # pristine, pre-profiler
    server = SchedulerServer(cluster, Scheduler()).start()
    try:
        prof = _get(server.port, "/debug/pprof/profile")
        assert prof["hottest"] and prof["total_seconds"] > 0
        # upload once ...
        assert _post(server.port, "/cluster", doc)["ok"]
        # ... run a cycle on the stored cluster ...
        out = _post(server.port, "/cycle/stored", {})
        assert len(out["bind_requests"]) == 4
        # ... then PATCH a delta (one new 1-pod group) instead of
        # re-shipping the document
        new_pg = {"name": "late", "queue": groups[0].queue,
                  "min_member": 1}
        # a PARTIAL pod document: unspecified fields (status, affinity,
        # ...) merge from defaults
        new_pod = {"name": "late-0", "group": "late",
                   "resources": {"accel": 1.0, "cpu": 1.0, "memory": 1.0}}
        assert _post(server.port, "/cluster/delta", {
            "pod_groups_upsert": [new_pg], "pods_upsert": [new_pod],
        })["ok"]
        out2 = _post(server.port, "/cycle/stored", {})
        assert any(b["pod"] == "late-0" for b in out2["bind_requests"])
    finally:
        server.stop()


# --- multiple Topology CRDs ---------------------------------------------

def test_two_topology_trees_resolve_independently():
    """Two Topology objects (network racks vs power zones): each gang
    constrains against ITS tree — ref topology_plugin.go building one
    domain tree per Topology CRD."""
    topo_net = apis.Topology(name="network",
                             levels=["net/rack", "kubernetes.io/hostname"])
    topo_pwr = apis.Topology(name="power",
                             levels=["pwr/zone", "kubernetes.io/hostname"])
    nodes = []
    for i in range(4):
        nodes.append(apis.Node(
            name=f"n{i}", allocatable=apis.ResourceVec(4.0, 32.0, 128.0),
            labels={"net/rack": f"r{i % 2}", "pwr/zone": f"z{i // 2}",
                    "kubernetes.io/hostname": f"n{i}"}))
    queues = [apis.Queue(name="dept", accel=apis.QueueResource(quota=16.0)),
              apis.Queue(name="q", parent="dept",
                         accel=apis.QueueResource(quota=16.0))]
    # rack r0 = {n0, n2}; zone z0 = {n0, n1}
    pg_net = apis.PodGroup(
        name="g-net", queue="q", min_member=2,
        topology_constraint=apis.TopologyConstraint(
            topology="network", required_level="net/rack"))
    pg_pwr = apis.PodGroup(
        name="g-pwr", queue="q", min_member=2,
        topology_constraint=apis.TopologyConstraint(
            topology="power", required_level="pwr/zone"))
    pods = [apis.Pod(name=f"{g}-{t}", group=g,
                     resources=apis.ResourceVec(2.0, 1.0, 1.0))
            for g in ("g-net", "g-pwr") for t in range(2)]
    cluster = Cluster.from_objects(nodes, queues, [pg_net, pg_pwr], pods,
                                   [topo_net, topo_pwr])
    res = Scheduler().run_once(cluster)
    by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
    assert len(by_pod) == 4
    net_nodes = {by_pod["g-net-0"], by_pod["g-net-1"]}
    pwr_nodes = {by_pod["g-pwr-0"], by_pod["g-pwr-1"]}
    racks = {{"n0": "r0", "n1": "r1", "n2": "r0", "n3": "r1"}[n]
             for n in net_nodes}
    zones = {{"n0": "z0", "n1": "z0", "n2": "z1", "n3": "z1"}[n]
             for n in pwr_nodes}
    assert len(racks) == 1, net_nodes   # g-net in ONE network rack
    assert len(zones) == 1, pwr_nodes   # g-pwr in ONE power zone


def test_multi_topology_snapshot_roundtrip():
    topo_a = apis.Topology(name="a", levels=["ra", "kubernetes.io/hostname"])
    topo_b = apis.Topology(name="b", levels=["zb", "kubernetes.io/hostname"])
    nodes, queues, groups, pods, _ = make_cluster(
        num_nodes=2, node_accel=2.0, num_gangs=1, tasks_per_gang=1)
    for i, n in enumerate(nodes):
        n.labels.update({"ra": f"r{i}", "zb": "z0"})
    cluster = Cluster.from_objects(nodes, queues, groups, pods,
                                   [topo_a, topo_b])
    back = snapshot.load_cluster(snapshot.dump_cluster(cluster))
    assert [t.name for t in back.topology] == ["a", "b"]
    assert len(Scheduler().run_once(back).bind_requests) == 1

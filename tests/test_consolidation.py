"""Consolidation action tests — ref
``actions/consolidation/consolidation_test.go``: defragment by moving
running preemptible jobs so a pending gang fits; every victim must be
re-placed (allPodsReallocated)."""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.ops.allocate import allocate, init_result
from kai_scheduler_tpu.ops.victims import VictimConfig, run_victim_action
from kai_scheduler_tpu.state import build_snapshot

Vec = apis.ResourceVec
QR = apis.QueueResource


def fragmented_cluster():
    """Two 4-accel nodes, each half-full with a 2-accel running pod.
    A pending gang needing 4 accel on ONE node fits only after moving one
    runner to the other node."""
    nodes = [apis.Node(f"node-{i}", Vec(4.0, 64.0, 256.0)) for i in range(2)]
    queues = [apis.Queue("q0", accel=QR(quota=8.0))]
    frag0 = apis.PodGroup("frag0", queue="q0", min_member=1,
                          last_start_timestamp=0.0)
    frag1 = apis.PodGroup("frag1", queue="q0", min_member=1,
                          creation_timestamp=0.5, last_start_timestamp=0.5)
    pending = apis.PodGroup("big", queue="q0", min_member=1,
                            creation_timestamp=1.0)
    pods = [
        apis.Pod("f0", "frag0", resources=Vec(2.0, 1.0, 4.0),
                 status=apis.PodStatus.RUNNING, node="node-0"),
        apis.Pod("f1", "frag1", resources=Vec(2.0, 1.0, 4.0),
                 status=apis.PodStatus.RUNNING, node="node-1"),
        apis.Pod("big-0", "big", resources=Vec(4.0, 1.0, 4.0),
                 creation_timestamp=1.0),
    ]
    return build_snapshot(nodes, queues, [frag0, frag1, pending], pods,
                          now=100.0)


def run_consolidate(state, num_levels=1, **cfg):
    fair_share = drf.set_fair_share(state, num_levels=num_levels)
    return run_victim_action(
        state, fair_share, init_result(state), num_levels=num_levels,
        mode="consolidate", config=VictimConfig(**cfg))


class TestConsolidation:
    def test_moves_runner_to_fit_pending_gang(self):
        state, index = fragmented_cluster()
        # sanity: plain allocate cannot place the 4-accel task
        fair_share = drf.set_fair_share(state, num_levels=1)
        plain = allocate(state, fair_share, num_levels=1)
        big = index.gang_names.index("big")
        assert not bool(plain.allocated[big])

        res = run_consolidate(state)
        assert bool(res.allocated[big])
        assert bool(res.pipelined[big, 0])
        victims = np.asarray(res.victim)
        moves = np.asarray(res.victim_move)
        assert victims.sum() == 1                 # exactly one runner moved
        vi = int(np.argmax(victims))
        assert moves[vi] >= 0                     # and it has a new home
        # the move target is the *other* node than the preemptor's
        big_node = int(np.asarray(res.placements)[big, 0])
        assert moves[vi] != big_node

    def test_no_consolidation_when_victims_cannot_be_replaced(self):
        """Full cluster: evicting a runner leaves nowhere to re-place it."""
        nodes = [apis.Node("node-0", Vec(4.0, 64.0, 256.0))]
        queues = [apis.Queue("q0", accel=QR(quota=8.0))]
        frag = apis.PodGroup("frag", queue="q0", min_member=1,
                             last_start_timestamp=0.0)
        pending = apis.PodGroup("big", queue="q0", min_member=1,
                                creation_timestamp=1.0)
        pods = [
            apis.Pod("f0", "frag", resources=Vec(2.0, 1.0, 4.0),
                     status=apis.PodStatus.RUNNING, node="node-0"),
            apis.Pod("big-0", "big", resources=Vec(4.0, 1.0, 4.0)),
        ]
        state, index = build_snapshot(nodes, queues, [frag, pending], pods,
                                      now=100.0)
        res = run_consolidate(state)
        assert not bool(res.allocated[index.gang_names.index("big")])
        assert int(np.asarray(res.victim).sum()) == 0

    def test_nonpreemptible_pending_gang_not_served(self):
        state, index = fragmented_cluster()
        groups = list(index.gang_names)
        # rebuild with a non-preemptible pending gang
        nodes = [apis.Node(f"node-{i}", Vec(4.0, 64.0, 256.0))
                 for i in range(2)]
        queues = [apis.Queue("q0", accel=QR(quota=8.0))]
        frag0 = apis.PodGroup("frag0", queue="q0", min_member=1,
                              last_start_timestamp=0.0)
        frag1 = apis.PodGroup("frag1", queue="q0", min_member=1,
                              last_start_timestamp=0.0)
        pending = apis.PodGroup(
            "big", queue="q0", min_member=1,
            preemptibility=apis.Preemptibility.NON_PREEMPTIBLE)
        pods = [
            apis.Pod("f0", "frag0", resources=Vec(2.0, 1.0, 4.0),
                     status=apis.PodStatus.RUNNING, node="node-0"),
            apis.Pod("f1", "frag1", resources=Vec(2.0, 1.0, 4.0),
                     status=apis.PodStatus.RUNNING, node="node-1"),
            apis.Pod("big-0", "big", resources=Vec(4.0, 1.0, 4.0)),
        ]
        state, index = build_snapshot(nodes, queues,
                                      [frag0, frag1, pending], pods,
                                      now=100.0)
        res = run_consolidate(state)
        assert not bool(res.allocated[index.gang_names.index("big")])


class TestConsolidationMoveCommit:
    """The commit path must *move* victims, not lose them — VERDICT r1 #3,
    ref ``consolidation.go`` allPodsReallocated + Statement pipelining."""

    def _cluster(self):
        nodes = [apis.Node(f"node-{i}", Vec(4.0, 64.0, 256.0))
                 for i in range(2)]
        queues = [apis.Queue("q0", accel=QR(quota=8.0))]
        frag0 = apis.PodGroup("frag0", queue="q0", min_member=1,
                              last_start_timestamp=0.0)
        frag1 = apis.PodGroup("frag1", queue="q0", min_member=1,
                              creation_timestamp=0.5,
                              last_start_timestamp=0.5)
        pending = apis.PodGroup("big", queue="q0", min_member=1,
                                creation_timestamp=1.0)
        pods = [
            apis.Pod("f0", "frag0", resources=Vec(2.0, 1.0, 4.0),
                     status=apis.PodStatus.RUNNING, node="node-0",
                     accel_devices=[0, 1]),
            apis.Pod("f1", "frag1", resources=Vec(2.0, 1.0, 4.0),
                     status=apis.PodStatus.RUNNING, node="node-1",
                     accel_devices=[0, 1]),
            apis.Pod("big-0", "big", resources=Vec(4.0, 1.0, 4.0),
                     creation_timestamp=1.0),
        ]
        from kai_scheduler_tpu.runtime import Cluster
        c = Cluster.from_objects(nodes, queues, [frag0, frag1, pending],
                                 pods)
        c.now = 100.0
        return c

    def test_victim_is_rebound_on_planned_node_and_preemptor_placed(self):
        from kai_scheduler_tpu.binder import Binder
        from kai_scheduler_tpu.framework import Scheduler

        cluster = self._cluster()
        sched, binder = Scheduler(), Binder()
        result = sched.run_once(cluster)

        # one victim evicted WITH a move target + a pipelined rebind
        assert len(result.evictions) == 1
        ev = result.evictions[0]
        assert ev.move_to is not None
        assert len(result.move_bind_requests) == 1
        assert result.move_bind_requests[0].pod_name == ev.pod_name
        victim_name, planned_node = ev.pod_name, ev.move_to

        # drive the world: release -> restart pending -> binder sweeps
        for _ in range(4):
            binder.reconcile(cluster)
            cluster.tick()
        binder.reconcile(cluster)
        cluster.tick()

        moved = cluster.pods[victim_name]
        assert moved.status == apis.PodStatus.RUNNING
        assert moved.node == planned_node

        # the preemptor won its space (bound this or a later cycle)
        sched.run_once(cluster)
        binder.reconcile(cluster)
        cluster.tick()
        big_pod = cluster.pods["big-0"]
        assert big_pod.status in (apis.PodStatus.BOUND,
                                  apis.PodStatus.RUNNING)
        # and it sits alone on its node (4 accel of 4)
        others = [p for p in cluster.pods.values()
                  if p.node == big_pod.node and p.name != big_pod.name
                  and p.status in (apis.PodStatus.BOUND,
                                   apis.PodStatus.RUNNING)]
        assert others == []

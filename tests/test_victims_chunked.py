"""Chunked-victim-wavefront equivalence properties (sparse + dense).

The PR-5 sparse-lane rework gives preempt two compiled paths — the
sparse/optimistic queue-disjoint wavefront and the dense composed
fallback — on top of the sequential B=1 scan (reference-exact).  These
properties pin, on randomized many-queue snapshots, that every path at
every lane width produces IDENTICAL placements and victim sets to the
sequential scan, and that the runtime dense fallback engages exactly
when a queue's unit count overflows the compact tables.
"""
import dataclasses

import numpy as np
import pytest

from kai_scheduler_tpu.framework.session import Session
from kai_scheduler_tpu.ops.allocate import init_result
from kai_scheduler_tpu.ops.victims import (_sparse_preempt_ok,
                                           run_victim_action_jit)
from kai_scheduler_tpu.state import make_cluster

WIDTHS = (1, 64, 256)


def _many_queue_session(seed, *, boost=100, tasks=2):
    """Randomized many-queue snapshot: 16 leaf queues, each with a
    boosted pending preemptor over a saturated share of running gangs —
    the production steady state the sparse path is built for."""
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=48, node_accel=2.0, num_gangs=64, tasks_per_gang=tasks,
        running_fraction=48 / 64, num_departments=2,
        queues_per_department=8, pending_priority_boost=boost, seed=seed)
    return Session.open(nodes, queues, groups, pods, topo)


def _run(ses, mode, cfg):
    import jax
    return jax.block_until_ready(run_victim_action_jit(
        ses.state, ses.state.queues.fair_share, init_result(ses.state),
        num_levels=2, mode=mode, config=cfg))


def _outs(res):
    return (np.asarray(res.allocated), np.asarray(res.victim),
            np.asarray(res.placements), np.asarray(res.pipelined))


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("path", ["sparse", "dense"])
def test_chunked_preempt_identical_to_sequential(seed, path):
    """Chunked preempt at every lane width — sparse/optimistic AND the
    forced dense composed path — must reproduce the sequential scan's
    placements and victim set bit-for-bit on the many-queue family."""
    ses = _many_queue_session(seed)
    # the Session auto-tune must have enabled the sparse protocol for
    # this shape (uniform, no devices/extended/subgroup topology)
    assert _sparse_preempt_ok(ses.config.victims)
    base = None
    for b in WIDTHS:
        cfg = dataclasses.replace(
            ses.config.victims, batch_size=b, batch_size_preempt=b,
            optimistic_preempt=(None if path == "sparse" else False))
        out = _outs(_run(ses, "preempt", cfg))
        if base is None:
            base = out          # B=1: the sequential reference scan
            assert base[0].any(), "family must exercise preemption"
            assert base[1].any()
        else:
            for got, want, name in zip(out, base,
                                       ("allocated", "victim",
                                        "placements", "pipelined")):
                np.testing.assert_array_equal(got, want, err_msg=name)


@pytest.mark.parametrize("seed", [0, 1])
def test_chunked_reclaim_identical_to_sequential(seed):
    """Chunked reclaim at every lane width vs the sequential scan on a
    partitioned over-quota snapshot: the same reclaimers admitted and
    the IDENTICAL victim set.  Node choice may drift among equal-scoring
    nodes (lanes place against chunk-start state — the documented
    composed-wavefront drift), so placements are compared as per-gang
    counts, not cells; the preempt test above pins full bit-equality
    for the sparse path."""
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=48, node_accel=4.0, num_gangs=24, tasks_per_gang=4,
        running_fraction=0.5, num_departments=2, queues_per_department=4,
        queue_accel_quota=8.0, partition_queues_by_running=True,
        seed=seed)
    ses = Session.open(nodes, queues, groups, pods, topo)
    base = None
    for b in WIDTHS:
        cfg = dataclasses.replace(ses.config.victims, batch_size=b,
                                  chunk_reclaim=True)
        out = _outs(_run(ses, "reclaim", cfg))
        if base is None:
            base = out
            assert base[0].any(), "family must exercise reclaim"
        else:
            np.testing.assert_array_equal(out[0], base[0],
                                          err_msg="allocated")
            np.testing.assert_array_equal(out[1], base[1],
                                          err_msg="victim")
            np.testing.assert_array_equal(
                (out[2] >= 0).sum(-1), (base[2] >= 0).sum(-1),
                err_msg="placement counts")


def test_wide_gang_family_identical_to_sequential():
    """8-task gangs over 8-accel nodes: each victim gang spreads across
    several nodes, so earlier placements' claims shift later lanes'
    density/availability score ties.  Before the canonical (node-
    ascending) replica assignment this family produced within-gang
    task→node PERMUTATIONS between the wavefront and the sequential
    scan (same node multiset, different cells) — pinned here
    bit-exact."""
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=256, node_accel=8.0, num_gangs=320, tasks_per_gang=8,
        running_fraction=256 / 320, num_departments=2,
        queues_per_department=32, pending_priority_boost=100, seed=3)
    ses = Session.open(nodes, queues, groups, pods, topo)
    assert _sparse_preempt_ok(ses.config.victims)
    base = None
    for b in (1, 64):
        cfg = dataclasses.replace(ses.config.victims, batch_size=b,
                                  batch_size_preempt=b)
        res = _run(ses, "preempt", cfg)
        out = _outs(res)
        if base is None:
            base = out
            assert base[0].any() and base[1].any()
        else:
            for got, want, name in zip(out, base,
                                       ("allocated", "victim",
                                        "placements", "pipelined")):
                np.testing.assert_array_equal(got, want, err_msg=name)
            # the steady-state family must stay demotion-free (the
            # exactness machinery must not serialize the wavefront)
            assert np.asarray(res.wavefront_stats)[1, 4] == 0


def _leftover_session():
    """Hand-built snapshot where an earlier lane's NET leftover freed
    capacity decides a later lane's placement: evicting queue A's 2-pod
    quorum gang on node-0 frees 2 accel but preemptor A consumes only 1,
    and the sequential scan then binpacks preemptor B onto that leftover
    (node-0) instead of its own victim's node-1."""
    from kai_scheduler_tpu.apis import types as apis
    Vec, QR = apis.ResourceVec, apis.QueueResource
    nodes = [apis.Node("node-0", Vec(2.0, 16.0, 64.0)),
             apis.Node("node-1", Vec(2.0, 16.0, 64.0))]
    queues = [apis.Queue("qa", accel=QR(quota=2.0), creation_timestamp=0.0),
              apis.Queue("qb", accel=QR(quota=2.0), creation_timestamp=1.0)]
    groups = [
        apis.PodGroup("victim-a", queue="qa", min_member=2, priority=0,
                      creation_timestamp=0.0, last_start_timestamp=0.0),
        apis.PodGroup("victim-b", queue="qb", min_member=1, priority=0,
                      creation_timestamp=1.0, last_start_timestamp=0.0),
        apis.PodGroup("filler-b", queue="qb", min_member=1, priority=200,
                      creation_timestamp=2.0, last_start_timestamp=0.0),
        apis.PodGroup("preemptor-a", queue="qa", min_member=1,
                      priority=100, creation_timestamp=10.0),
        apis.PodGroup("preemptor-b", queue="qb", min_member=1,
                      priority=100, creation_timestamp=11.0),
    ]
    pods = [apis.Pod(f"va-{i}", "victim-a", resources=Vec(1.0, 1.0, 4.0),
                     status=apis.PodStatus.RUNNING, node="node-0",
                     creation_timestamp=0.0) for i in range(2)]
    pods += [
        apis.Pod("vb-0", "victim-b", resources=Vec(1.0, 1.0, 4.0),
                 status=apis.PodStatus.RUNNING, node="node-1",
                 creation_timestamp=1.0),
        apis.Pod("fb-0", "filler-b", resources=Vec(1.0, 1.0, 4.0),
                 status=apis.PodStatus.RUNNING, node="node-1",
                 creation_timestamp=2.0),
        apis.Pod("ga-0", "preemptor-a", resources=Vec(1.0, 1.0, 4.0),
                 creation_timestamp=10.0),
        apis.Pod("gb-0", "preemptor-b", resources=Vec(1.0, 1.0, 4.0),
                 creation_timestamp=11.0),
    ]
    return Session.open(nodes, queues, groups, pods)


@pytest.mark.parametrize("path", ["sparse", "dense"])
def test_leftover_freed_capacity_stays_sequential(path):
    """Net-leftover regression: a lane whose victims free MORE than its
    claims consume demotes later same-chunk lanes to conflict-retry, so
    the retried lane re-solves with exact composed inputs (and no
    own-freed bias) and lands where the sequential scan does.  Without
    the demotion both wavefront paths silently placed preemptor B on
    node-1 while the sequential scan binpacks it onto node-0's leftover."""
    ses = _leftover_session()
    assert _sparse_preempt_ok(ses.config.victims)
    base = None
    for b in WIDTHS[:2] + (4,):
        cfg = dataclasses.replace(
            ses.config.victims, batch_size=b, batch_size_preempt=b,
            optimistic_preempt=(None if path == "sparse" else False))
        res = _run(ses, "preempt", cfg)
        out = _outs(res)
        if base is None:
            base = out
            assert base[0].any() and base[1].any()
        else:
            for got, want, name in zip(out, base,
                                       ("allocated", "victim",
                                        "placements", "pipelined")):
                np.testing.assert_array_equal(got, want, err_msg=name)
            # the wide chunk must have exercised the demotion
            assert np.asarray(res.wavefront_stats)[1, 4] >= 1


def test_sparse_overflow_falls_back_dense():
    """A queue whose candidate-unit count overflows the compact tables
    must take the dense composed path (identical result, fallback
    counted in wavefront_stats)."""
    # 2 leaf queues × 10 running gangs each: >8 candidate units per
    # queue, so a sparse_unit_k=8 table overflows at run time while the
    # padded pod axis (>8) keeps the overflow cond live
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=24, node_accel=2.0, num_gangs=24, tasks_per_gang=2,
        running_fraction=20 / 24, num_departments=1,
        queues_per_department=2, pending_priority_boost=100, seed=0)
    ses = Session.open(nodes, queues, groups, pods, topo)
    assert _sparse_preempt_ok(ses.config.victims)
    cfg_lo = dataclasses.replace(ses.config.victims, batch_size=64,
                                 batch_size_preempt=64, sparse_unit_k=8)
    cfg_hi = dataclasses.replace(ses.config.victims, batch_size=64,
                                 batch_size_preempt=64)
    res_lo = _run(ses, "preempt", cfg_lo)
    res_hi = _run(ses, "preempt", cfg_hi)
    stats_lo = np.asarray(res_lo.wavefront_stats)
    stats_hi = np.asarray(res_hi.wavefront_stats)
    assert stats_lo[1, 3] == 1, stats_lo     # fell back to dense
    assert stats_hi[1, 3] == 0, stats_hi     # sparse path held
    assert stats_hi[1, 0] >= 1               # chunks counted
    assert 0 < stats_hi[1, 1] <= stats_hi[1, 2]  # occupancy sane
    for got, want in zip(_outs(res_lo), _outs(res_hi)):
        np.testing.assert_array_equal(got, want)


def test_auto_tune_clamps_lane_width_to_pending_spread():
    """Session auto-tuning v2: the preempt lane width follows the
    snapshot's live preemptor count (pow2-bucketed), not a fixed
    constant — junk lanes past the pending spread stop paying the
    per-lane freed-pool cost."""
    ses = _many_queue_session(0)
    bsp = ses.config.victims.batch_size_preempt
    pending = ses.index.num_pending_gangs
    assert pending == 16
    assert bsp == 16                         # pow4ceil(16)
    assert ses.config.victims.sparse_unit_k >= 8

"""Metrics-catalog meta-tests (tier-1): the registry and the generated
``docs/metrics/METRICS.md`` must agree EXACTLY — name, type, labels,
help — so the catalog can never silently drift (the reference ships
``docs/metrics/METRICS.md`` as a maintained artifact; ours is
generated and drift-gated instead).

Three directions are pinned:

1. live registry  == committed doc     (the doc is truthful);
2. lint extractor == live registry     (scripts/lint.py's jax-free AST
   extraction stays honest, so the pre-commit gate checks the same
   facts this test does);
3. render/parse round-trips            (the doc format is lossless).
"""
import importlib.util
import os

import pytest

from kai_scheduler_tpu.framework import metrics
from kai_scheduler_tpu.utils.metrics import parse_catalog, render_catalog

pytestmark = pytest.mark.core

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(ROOT, "docs", "metrics", "METRICS.md")


def _normalized_registry():
    rows = metrics.catalog()
    for r in rows:
        r["help"] = " ".join(str(r["help"]).split())
    return rows


def _load_lint_module():
    spec = importlib.util.spec_from_file_location(
        "kai_lint_wrapper", os.path.join(ROOT, "scripts", "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_catalog_doc_exists_and_matches_registry_exactly():
    assert os.path.exists(DOC), (
        "docs/metrics/METRICS.md missing — regenerate with "
        "`python -m kai_scheduler_tpu.framework.metrics`")
    with open(DOC, encoding="utf-8") as f:
        doc_rows = parse_catalog(f.read())
    assert doc_rows == _normalized_registry(), (
        "docs/metrics/METRICS.md drifted from the registry — "
        "regenerate with `python -m kai_scheduler_tpu.framework."
        "metrics > docs/metrics/METRICS.md`")


def test_lint_ast_extraction_matches_registry():
    """The jax-free extractor scripts/lint.py uses must see the same
    catalog the live registry reports — otherwise the pre-commit gate
    and this tier-1 gate could certify different facts."""
    lint = _load_lint_module()
    assert lint.registered_metrics_ast() == _normalized_registry()
    assert lint.check_metrics_doc() == []


def test_render_parse_round_trip():
    rows = _normalized_registry()
    assert parse_catalog(render_catalog(rows)) == rows


def test_every_metric_has_help_and_kai_prefix():
    for r in metrics.catalog():
        assert r["name"].startswith("kai_"), r["name"]
        assert r["help"].strip(), f"{r['name']} has no help text"

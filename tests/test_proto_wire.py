"""Sidecar protobuf wire protocol (SURVEY §7d; VERDICT r3 item 7).

The same endpoints the JSON sidecar uses accept/emit the typed protobuf
schema of ``wire/sidecar.proto`` when Content-Type is
``application/x-protobuf``: upload a ClusterDoc, PATCH ClusterDeltas,
drive cycles, get CommitSets back.
"""
import urllib.request

import pytest

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.server import SchedulerServer
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.wire import codec
from kai_scheduler_tpu.wire import sidecar_pb2 as pb


def _cluster():
    nodes = [apis.Node(name=f"n{i}",
                       allocatable=apis.ResourceVec(4.0, 64.0, 256.0),
                       labels={"kubernetes.io/hostname": f"n{i}"})
             for i in range(2)]
    queues = [apis.Queue(name="dept"),
              apis.Queue(name="q0", parent="dept",
                         accel=apis.QueueResource(quota=8.0))]
    groups = [apis.PodGroup(name="g0", queue="q0", min_member=2)]
    pods = [apis.Pod(name=f"g0-{i}", group="g0",
                     resources=apis.ResourceVec(1.0, 1.0, 1.0),
                     labels={"app": "x"},
                     tolerations=[apis.Toleration(key="k")],
                     pod_affinity=[apis.PodAffinityTerm(
                         match_labels=(("app", "x"),), anti=False,
                         required=False)])
            for i in range(2)]
    return Cluster.from_objects(nodes, queues, groups, pods, None)


def _post(port, path, msg, resp_cls):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=msg.SerializeToString(),
        headers={"Content-Type": "application/x-protobuf"})
    with urllib.request.urlopen(req, timeout=300) as resp:
        assert resp.headers["Content-Type"] == "application/x-protobuf"
        out = resp_cls()
        out.ParseFromString(resp.read())
        return out


def test_codec_roundtrip_preserves_objects():
    cluster = _cluster()
    doc = codec.cluster_to_msg(cluster)
    back = codec.cluster_from_msg(doc)
    assert sorted(back.nodes) == sorted(cluster.nodes)
    p0 = back.pods["g0-0"]
    assert p0.tolerations[0].key == "k"
    assert p0.pod_affinity[0].match_labels == (("app", "x"),)
    assert back.pod_groups["g0"].min_member == 2
    assert back.queues["q0"].accel.quota == 8.0


def test_cycle_roundtrip_through_proto_framing():
    """Upload the cluster as proto, run a cycle, check the CommitSet —
    and that the commit matches the JSON wire's result."""
    cluster = _cluster()
    server = SchedulerServer(_cluster()).start()
    try:
        doc = codec.cluster_to_msg(cluster)
        commit = _post(server.port, "/cycle", doc, pb.CommitSet)
        binds = {b.pod_name: b.selected_node for b in commit.bind_requests}
        assert set(binds) == {"g0-0", "g0-1"}
        assert all(n in ("n0", "n1") for n in binds.values())
        assert len(commit.evictions) == 0
    finally:
        server.stop()


def test_stored_cluster_and_delta_through_proto():
    server = SchedulerServer(_cluster()).start()
    try:
        cluster = _cluster()
        _post(server.port, "/cluster", codec.cluster_to_msg(cluster),
              pb.CommitSet)
        # delta: add a second gang (complete objects)
        delta = pb.ClusterDelta()
        codec.to_msg(apis.PodGroup(name="g1", queue="q0", min_member=1),
                     delta.pod_groups_upsert.add())
        codec.to_msg(apis.Pod(name="g1-0", group="g1",
                              resources=apis.ResourceVec(1.0, 1.0, 1.0)),
                     delta.pods_upsert.add())
        delta.now = 5.0
        _post(server.port, "/cluster/delta", delta, pb.CommitSet)
        commit = _post(server.port, "/cycle/stored", pb.ClusterDoc(),
                       pb.CommitSet)
        binds = {b.pod_name for b in commit.bind_requests}
        assert "g1-0" in binds and "g0-0" in binds
    finally:
        server.stop()

"""Prometheus query-construction layer (VERDICT r3 item 8): the
constructed PromQL matches the reference's shapes, the cron reset
resolves, and — the parity property — a mock Prometheus backend that
numerically evaluates the constructed queries over a synthetic
allocation series yields the same normalized usage as the host-side
accumulator integrating the same series.
"""
import numpy as np

from kai_scheduler_tpu.apis.types import (NUM_RESOURCES, RESOURCE_ACCEL,
                                          RESOURCE_CPU)
from kai_scheduler_tpu.runtime.usagedb import UsageLister, UsageParams
from kai_scheduler_tpu.runtime.usagedb_prometheus import (
    QUEUE_LABEL, PrometheusUsageClient, PrometheusUsageLister,
    decay_query, latest_cron_reset, sliding_window_query,
    tumbling_window_query)


def test_query_shapes_match_reference():
    p = UsageParams(half_life_s=3600.0)
    d = decay_query(1000.0, 3600.0)
    assert d == "0.5^((1000 - time()) / 3600.000000)"
    q = sliding_window_query("kai_queue_allocated_gpus", 1000.0, p)
    assert q == ("sum_over_time((((kai_queue_allocated_gpus) * "
                 "(0.5^((1000 - time()) / 3600.000000))))[14400s:60s])")
    t = tumbling_window_query("kai_queue_allocated_gpus", 1000.0,
                              UsageParams(window_type="tumbling",
                                          half_life_s=None))
    assert t == "sum_over_time(kai_queue_allocated_gpus)"


def test_latest_cron_reset():
    import datetime as dt
    now = dt.datetime(2026, 7, 30, 15, 42,
                      tzinfo=dt.timezone.utc).timestamp()
    # daily at midnight
    r = latest_cron_reset("0 0 * * *", now)
    assert r == dt.datetime(2026, 7, 30, 0, 0,
                            tzinfo=dt.timezone.utc).timestamp()
    # hourly on the half hour
    r = latest_cron_reset("30 * * * *", now)
    assert r == dt.datetime(2026, 7, 30, 15, 30,
                            tzinfo=dt.timezone.utc).timestamp()


class _MockProm:
    """Evaluates the constructed queries numerically over a synthetic
    step series — a Prometheus stand-in for exactly the query shapes
    this layer emits."""

    def __init__(self, series, capacity, step_s=60.0):
        #: series: {queue: {metric value at any t}} as a callable(t)
        self.series = series
        self.capacity = capacity
        self.step = step_s

    def _sum_over(self, fn, start, end, anchor, half_life):
        ts = np.arange(start, end + 1e-9, self.step)
        vals = np.asarray([fn(t) for t in ts], np.float64)
        if half_life:
            vals = vals * 0.5 ** ((anchor - ts) / half_life)
        return float(vals.sum())

    def __call__(self, path, query):
        expr = query["query"]
        # parse out our own constructions
        half_life = None
        if "0.5^((" in expr:
            inner = expr.split("0.5^((", 1)[1]
            anchor = float(inner.split(" - time()")[0])
            half_life = float(inner.split("/ ", 1)[1].split(")")[0])
        else:
            anchor = 0.0
        import re
        metric = re.search(r"kai_[a-z_]+", expr).group(0)
        if path == "/api/v1/query":
            end = float(query["time"])
            window = float(expr.rsplit("[", 1)[1].split("s:")[0])
            start = end - window + self.step
        else:
            start, end = float(query["start"]), float(query["end"])
        rows = []
        src = (self.series if not metric.startswith("kai_cluster")
               else {"": lambda t: self.capacity})
        for queue, fn in src.items():
            v = self._sum_over(fn, start, end, anchor, half_life)
            rows.append({"metric": {QUEUE_LABEL: queue},
                         "value": [end, str(v)],
                         "values": [[end, str(v)]]})
        return {"data": {"result": rows}}


def test_parity_with_accumulator_on_synthetic_series():
    """Same synthetic series through (a) the host accumulator and
    (b) the mock-Prometheus query layer → same normalized usage within
    discretization tolerance."""
    hl = 1800.0
    step = 60.0
    alloc = {"qa": lambda t: 4.0 if t >= 1800 else 0.0,
             "qb": lambda t: 2.0}
    capacity = 8.0
    params = UsageParams(half_life_s=hl, fetch_interval_s=step)

    # (a) accumulator integrating the instantaneous series
    acc = UsageLister(
        client=lambda now: {
            q: np.asarray([fn(now), 0, 0], np.float32)[:NUM_RESOURCES]
            for q, fn in alloc.items()},
        params=params,
        capacity_fn=lambda now: np.asarray(
            [capacity, 0, 0], np.float32)[:NUM_RESOURCES])
    t = 0.0
    while t <= 7200.0:
        acc.fetch(t)
        t += step
    usage_acc = acc.queue_usage(7200.0)

    # (b) the Prometheus layer against the mock backend
    client = PrometheusUsageClient(
        params=params,
        allocation_metrics={RESOURCE_ACCEL: "kai_queue_allocated_gpus"},
        capacity_metrics={RESOURCE_ACCEL: "kai_cluster_capacity_gpus"},
        http_get=_MockProm(alloc, capacity, step),
        resolution_s=step)
    usage_prom = client.fetch_usage(7200.0)

    for q in ("qa", "qb"):
        a = usage_acc[q][RESOURCE_ACCEL]
        b = usage_prom[q][RESOURCE_ACCEL]
        assert abs(a - b) < 0.05, (q, a, b)
    # qa used 4 GPUs for the recent half, qb 2 throughout: qa's decayed
    # share must exceed qb's
    assert usage_prom["qa"][RESOURCE_ACCEL] > usage_prom["qb"][RESOURCE_ACCEL]


def test_lister_staleness_degrades():
    client = PrometheusUsageClient(
        http_get=lambda path, q: (_ for _ in ()).throw(OSError("down")))
    lister = PrometheusUsageLister(client)
    assert not lister.maybe_fetch(0.0)
    assert lister.queue_usage(0.0) is None  # dead pipeline: no usage

    ok_client = PrometheusUsageClient(
        params=UsageParams(half_life_s=None, fetch_interval_s=60.0),
        allocation_metrics={RESOURCE_ACCEL: "kai_queue_allocated_gpus"},
        capacity_metrics={},
        http_get=_MockProm({"qa": lambda t: 1.0}, 1.0))
    lister2 = PrometheusUsageLister(ok_client)
    assert lister2.maybe_fetch(0.0)
    assert lister2.queue_usage(10.0) is not None
    # past stalenessPeriod (5x fetch interval) the data is rejected
    assert lister2.queue_usage(1000.0) is None

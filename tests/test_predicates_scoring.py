"""Predicate-mask + scoring kernel tests — analogue of
``plugins/predicates`` and ``plugins/nodeplacement/{nodepack,nodespread}_test.go``."""
import jax.numpy as jnp
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.ops import predicates, scoring
from kai_scheduler_tpu.state import build_snapshot, make_cluster

import pytest

pytestmark = pytest.mark.core


def small_state(**kw):
    nodes, queues, groups, pods, topo = make_cluster(**kw)
    return build_snapshot(nodes, queues, groups, pods, topo)


def test_resource_fit_basic():
    state, _ = small_state(num_nodes=4, node_accel=8.0)
    req = jnp.asarray([[4.0, 1.0, 1.0], [9.0, 1.0, 1.0]])  # fits / too big
    sel = jnp.full((2, state.nodes.labels.shape[1]), -1, jnp.int32)
    mask = predicates.feasible_nodes(state.nodes, req, sel)
    m = np.asarray(mask)
    assert m[0, :4].all()          # 4 accel fits every 8-accel node
    assert not m[1].any()          # 9 accel fits nowhere
    assert not m[:, 4:].any()      # padded nodes never feasible


def test_selector_mask():
    nodes = [
        apis.Node("a", apis.ResourceVec(8, 8, 8), labels={"zone": "east"}),
        apis.Node("b", apis.ResourceVec(8, 8, 8), labels={"zone": "west"}),
    ]
    queues = [apis.Queue("q")]
    groups = [apis.PodGroup("g", queue="q", min_member=1)]
    pods = [apis.Pod("p", "g", apis.ResourceVec(1, 1, 1),
                     node_selector={"zone": "west"})]
    state, idx = build_snapshot(nodes, queues, groups, pods)
    mask = predicates.feasible_nodes(
        state.nodes, state.gangs.task_req[0, 0],
        state.gangs.task_selector[0, 0])
    m = np.asarray(mask)
    assert not m[idx.node_index("a")]
    assert m[idx.node_index("b")]


def test_fractional_portion_fit():
    state, _ = small_state(num_nodes=2, node_accel=1.0)
    req = jnp.asarray([2.0, 1.0, 1.0])     # 2 whole devices: doesn't fit
    sel = jnp.full((state.nodes.labels.shape[1],), -1, jnp.int32)
    whole = predicates.feasible_nodes(state.nodes, req, sel)
    assert not np.asarray(whole)[:2].any()
    # same pod as a 0.5-device fraction fits
    frac = predicates.feasible_nodes(
        state.nodes, req, sel, task_portion=jnp.asarray(0.5))
    assert np.asarray(frac)[:2].all()


def test_releasing_enables_pipeline_fit():
    state, _ = small_state(num_nodes=2, node_accel=2.0)
    free = state.nodes.free.at[0].set(jnp.asarray([0.0, 64.0, 256.0]))
    nodes = state.nodes.replace(
        free=free,
        releasing=state.nodes.releasing.at[0].set(jnp.asarray([2.0, 0.0, 0.0])))
    req = jnp.asarray([1.0, 1.0, 1.0])
    sel = jnp.full((nodes.labels.shape[1],), -1, jnp.int32)
    idle = predicates.feasible_nodes(nodes, req, sel)
    pipe = predicates.feasible_nodes(nodes, req, sel, include_releasing=True)
    assert not np.asarray(idle)[0] and np.asarray(pipe)[0]
    assert np.asarray(idle)[1]


def test_binpack_prefers_fuller_node():
    """ref nodeplacement/pack.go getScoreOfCurrentNode: fewer non-allocated
    resources => higher score under binpack; reversed under spread."""
    state, _ = small_state(num_nodes=2, node_accel=8.0)
    # node 0 fuller (2 free), node 1 empty (8 free)
    free = state.nodes.free.at[0, apis.RESOURCE_ACCEL].set(2.0)
    req = jnp.asarray([[1.0, 1.0, 1.0]])
    fit = jnp.asarray([[True, True] + [False] * (state.nodes.n - 2)])
    pack = scoring.placement_score(
        state.nodes, free, req, fit, scoring.PlacementConfig(binpack_accel=True))
    spread = scoring.placement_score(
        state.nodes, free, req, fit, scoring.PlacementConfig(binpack_accel=False))
    p, s = np.asarray(pack)[0], np.asarray(spread)[0]
    assert p[0] > p[1]
    assert s[1] > s[0]
    assert p.max() == scoring.MAX_HIGH_DENSITY


def test_score_bands_compose():
    """Availability band must dominate any density difference
    (scores.go band ordering)."""
    state, _ = small_state(num_nodes=2, node_accel=8.0)
    req = jnp.asarray([[1.0, 1.0, 1.0]])
    fit_pipe = jnp.asarray([[True, True] + [False] * (state.nodes.n - 2)])
    fit_idle = jnp.asarray([[False, True] + [False] * (state.nodes.n - 2)])
    total = scoring.score_nodes_for_task(
        state.nodes, state.nodes.free, req, fit_idle, fit_pipe)
    t = np.asarray(total)[0]
    assert t[1] > t[0]                      # idle-fitting node wins
    assert t[2] <= scoring.BIG_NEG          # infeasible masked off


def test_cpu_only_task_prefers_cpu_node():
    nodes = [
        apis.Node("gpu", apis.ResourceVec(8, 32, 128)),
        apis.Node("cpu", apis.ResourceVec(0, 32, 128)),
    ]
    queues = [apis.Queue("q")]
    state, idx = build_snapshot(nodes, queues, [], [])
    req = jnp.asarray([[0.0, 4.0, 8.0]])
    s = scoring.resource_type_score(state.nodes, req)
    arr = np.asarray(s)[0]
    assert arr[idx.node_index("cpu")] == scoring.W_RESOURCE_TYPE
    assert arr[idx.node_index("gpu")] == 0.0

"""Multi-cycle soak: scheduler + binder + cluster lifecycle under churn.

The reference's envtest/e2e tiers (SURVEY §4) drive the real scheduler,
binder and controllers together against a live cluster.  This is that
tier in-process: randomized workloads arrive and complete over many
cycles; after EVERY cycle+bind+tick the cluster-wide invariants must
hold:

- node capacity is never exceeded by bound/running pods,
- no accelerator device is double-booked (whole or fractional),
- gang all-or-nothing EVENTUALLY: placement is all-or-nothing in-kernel,
  but commits pipeline tasks that landed on releasing capacity into
  later cycles, so a gang may be transiently part-bound; once the
  system drains (no new arrivals), no gang may remain part-bound below
  quorum,
- every BindRequest a cycle cuts names a pod that was PENDING when the
  cycle ran.
"""
import random

import pytest

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.binder.binder import Binder
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.runtime.cluster import Cluster

pytestmark = pytest.mark.slow


def _check_invariants(cluster: Cluster, final: bool = False):
    # capacity + device booking per node
    for node in cluster.nodes.values():
        used = apis.ResourceVec()
        device_share: dict[int, float] = {}
        for pod in cluster.pods.values():
            if pod.node != node.name or pod.status not in (
                    apis.PodStatus.BOUND, apis.PodStatus.RUNNING,
                    apis.PodStatus.RELEASING):
                continue
            used = used + pod.resources
            if pod.accel_portion > 0:
                for d in pod.accel_devices:
                    device_share[d] = device_share.get(d, 0.0) \
                        + pod.accel_portion
            else:
                for d in pod.accel_devices:
                    device_share[d] = device_share.get(d, 0.0) + 1.0
        assert used.cpu <= node.allocatable.cpu + 1e-6, node.name
        assert used.memory <= node.allocatable.memory + 1e-6, node.name
        for d, share in device_share.items():
            assert d < int(round(node.allocatable.accel)), (node.name, d)
            assert share <= 1.0 + 1e-6, (node.name, d, share)
    # gang wholeness: strict only once the system has drained —
    # transiently a gang may be part-bound while its remaining tasks
    # are pipelined into later cycles (placed on releasing capacity)
    if final:
        for group in cluster.pod_groups.values():
            bound = sum(
                p.status in (apis.PodStatus.BOUND, apis.PodStatus.RUNNING)
                for p in cluster.pods.values() if p.group == group.name)
            total = sum(1 for p in cluster.pods.values()
                        if p.group == group.name)
            assert not (0 < bound < min(group.min_member, total)), (
                group.name, bound, group.min_member, total)


@pytest.mark.parametrize("seed", [7, 21])
def test_lifecycle_soak(seed):
    rng = random.Random(seed)
    nodes = [apis.Node(name=f"n{i}",
                       allocatable=apis.ResourceVec(4.0, 16.0, 64.0))
             for i in range(8)]
    queues = [apis.Queue(name="dept", accel=apis.QueueResource(quota=32.0)),
              apis.Queue(name="qa", parent="dept",
                         accel=apis.QueueResource(quota=16.0)),
              apis.Queue(name="qb", parent="dept",
                         accel=apis.QueueResource(quota=16.0))]
    cluster = Cluster.from_objects(nodes, queues, [], [])
    sched = Scheduler()
    binder = Binder()
    gang_seq = 0
    placed_total = 0

    for cycle in range(10):
        # churn: a few new gangs arrive...
        for _ in range(rng.randint(1, 3)):
            size = rng.randint(1, 4)
            gname = f"g{gang_seq}"
            gang_seq += 1
            pg = apis.PodGroup(name=gname,
                               queue=rng.choice(["qa", "qb"]),
                               min_member=size)
            pods = []
            for t in range(size):
                frac = rng.random() < 0.2
                pods.append(apis.Pod(
                    name=f"{gname}-{t}", group=gname,
                    resources=apis.ResourceVec(
                        0.0 if frac else float(rng.randint(1, 2)),
                        1.0, 2.0),
                    accel_portion=0.5 if frac else 0.0))
            cluster.submit(pg, pods)
        # ... and a running gang occasionally completes
        running_groups = sorted({
            p.group for p in cluster.pods.values()
            if p.status == apis.PodStatus.RUNNING})
        if running_groups and rng.random() < 0.5:
            done = rng.choice(running_groups)
            for p in list(cluster.pods.values()):
                if p.group == done:
                    cluster.evict_pod(p.name)

        pending_before = {p.name for p in cluster.pods.values()
                          if p.status == apis.PodStatus.PENDING}
        result = sched.run_once(cluster)
        placed_total += len(result.bind_requests)
        for br in result.bind_requests:
            assert br.pod_name in pending_before, br.pod_name
        bind = binder.reconcile(cluster)
        assert not bind.failed, bind.failed
        _check_invariants(cluster)
        cluster.tick()
        _check_invariants(cluster)

    assert placed_total > 0
    # the system drains: with enough repeat cycles and no new arrivals,
    # everything pending either places or is genuinely over capacity —
    # and no gang may remain part-bound below quorum
    for _ in range(5):
        sched.run_once(cluster)
        binder.reconcile(cluster)
        cluster.tick()
        _check_invariants(cluster)
    _check_invariants(cluster, final=True)
